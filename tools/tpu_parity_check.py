"""On-chip kernel parity checks (ADVICE r3 item 2).

The CPU test suite pins kernel parity in INTERPRET mode only; Mosaic
compilation is a different code path (layout, MXU accumulation order,
select legalization).  This tool runs the Pallas kernels on the REAL
chip against their jnp reference implementations:

  search    — search2_pallas_raw vs find_best_split_leaves: integer-
              exact histograms (any summation order exact -> bitwise
              comparable decisions) plus float histograms at tolerance
  split     — split_step_window (mega kernel) vs partition_window +
              histogram_single_leaf_raw + search2_update_pallas
  writeback — write_window (aliased DMA) vs dynamic_update_slice

Exits non-zero on any mismatch; prints one summary line per check.
Run when a TPU window is live:  python tools/tpu_parity_check.py
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def log(msg):
    print(msg, flush=True)


def check_search(rng) -> bool:
    import jax.numpy as jnp

    from lightgbm_tpu.ops.pallas_search import search2_pallas_raw
    from lightgbm_tpu.ops.split import find_best_split_leaves
    from lightgbm_tpu.learners.serial import TreeLearnerParams
    from lightgbm_tpu.config import Config

    F, B = 12, 64
    Fp, Bp = 16, 128
    ok = True
    for trial, integer in ((0, True), (1, True), (2, False)):
        if integer:  # exact under ANY accumulation order
            hg = rng.randint(-8, 9, (2, F, B)).astype(np.float32)
            hh = rng.randint(1, 5, (2, F, B)).astype(np.float32)
        else:
            hg = rng.randn(2, F, B).astype(np.float32)
            hh = (rng.rand(2, F, B) + 0.1).astype(np.float32)
        hc = rng.randint(1, 50, (2, F, B)).astype(np.float32)
        # tie case: duplicate the best feature's histogram onto a higher
        # index — the smaller feature must win (split_info.hpp:98-103)
        hg[:, 7] = hg[:, 3]
        hh[:, 7] = hh[:, 3]
        hc[:, 7] = hc[:, 3]
        h2 = np.zeros((2, Fp, 4, Bp), np.float32)
        h2[:, :F, 0, :B] = hg
        h2[:, :F, 1, :B] = hh
        h2[:, :F, 2, :B] = hc
        sums = h2.sum(axis=3)  # [2, Fp, 4]
        lsg, lsh, lc = sums[0, :F, 0].sum() / F, sums[0, :F, 1].sum() / F, \
            sums[0, :F, 2].sum() / F
        rsg, rsh, rc = sums[1, :F, 0].sum() / F, sums[1, :F, 1].sum() / F, \
            sums[1, :F, 2].sum() / F
        prm = TreeLearnerParams.from_config(
            Config(min_data_in_leaf=5, min_sum_hessian_in_leaf=1e-3))
        args = (jnp.float32(lsg), jnp.float32(lsh), jnp.float32(lc),
                jnp.float32(rsg), jnp.float32(rsh), jnp.float32(rc))
        fmask = jnp.ones(F, bool)
        nbpf = jnp.full(F, B, jnp.int32)
        iscat = jnp.zeros(F, bool)
        rl, rr = search2_pallas_raw(
            jnp.asarray(h2), *args, jnp.bool_(True), fmask, nbpf, iscat,
            prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
            prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split,
            interpret=False)
        hist = jnp.asarray(
            np.stack([np.stack([hg[c], hh[c], hc[c]], -1) for c in (0, 1)]))
        ref = find_best_split_leaves(
            hist, jnp.asarray([lsg, rsg]), jnp.asarray([lsh, rsh]),
            jnp.asarray([lc, rc]), fmask, nbpf, iscat,
            prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
            prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split,
            jnp.asarray([True, True]))
        for c, r in ((0, rl), (1, rr)):
            f_k, t_k = int(r.feature), int(r.threshold)
            f_j, t_j = int(ref.feature[c]), int(ref.threshold[c])
            g_k, g_j = float(r.gain), float(ref.gain[c])
            if integer:
                same = (f_k == f_j and t_k == t_j)
            else:  # float: decisions may differ only at near-ties
                same = (f_k == f_j and t_k == t_j) or abs(
                    g_k - g_j) <= 1e-4 * max(1.0, abs(g_j))
            if not same:
                log(f"  search MISMATCH trial {trial} child {c}: "
                    f"kernel (f={f_k}, t={t_k}, g={g_k}) vs "
                    f"jnp (f={f_j}, t={t_j}, g={g_j})")
                ok = False
    log(f"search parity: {'OK' if ok else 'FAIL'}")
    return ok


def check_split(rng) -> bool:
    import jax.numpy as jnp

    from lightgbm_tpu.ops.pallas_histogram import histogram_single_leaf_raw
    from lightgbm_tpu.ops.pallas_search import (
        _pack_meta, _pack_scal, search2_update_pallas)
    from lightgbm_tpu.ops.record import (
        TILE, bins_per_word, build_record, extract_feature,
        partition_window, round_up, split_step_window)

    F, n, num_bins, L = 11, 5000, 37, 7
    bins = rng.randint(0, num_bins, (F, n)).astype(np.uint8)
    g = rng.randn(n).astype(np.float32)
    h = (rng.rand(n) + 0.5).astype(np.float32)
    bag = (rng.rand(n) > 0.2).astype(np.float32)
    k = bins_per_word(jnp.uint8)
    cap = round_up(n, TILE)
    rec = build_record(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                       jnp.asarray(bag), cap + TILE)
    Fp, Bp = round_up(F, 8), round_up(num_bins, 128)
    hists_np = np.zeros((L, Fp, 4, Bp), np.float32)
    hists_np[0] = np.asarray(histogram_single_leaf_raw(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(bag), num_bins=num_bins))
    f, thr = 4, 11
    fv = extract_feature(rec, jnp.int32(f), jnp.int32(0), cap, k)
    go = (fv <= thr).astype(jnp.int32)
    meta = _pack_meta(jnp.ones(F, bool), jnp.full(F, num_bins, jnp.int32),
                      jnp.zeros(F, bool), Fp)
    scal_args = [jnp.float32(x) for x in
                 (1.0, 1., 2., 300., -1., 2., 300.)]
    lim_args = [jnp.float32(x) for x in (20., 1e-3, 0., 0., 0.)]
    scal = _pack_scal(*(scal_args + lim_args))

    recA, nlA = partition_window(
        rec, go, jnp.int32(0), jnp.int32(n), jnp.bool_(True), cap)
    govm = np.asarray(go).astype(bool) & (np.arange(cap) < n)
    from lightgbm_tpu.ops.record import unpack_window
    import jax
    win = jax.lax.dynamic_slice(rec, (0, 0), (rec.shape[0], cap))
    bw, gw, hw, mw = unpack_window(win, F, k, jnp.uint8)
    h_left = histogram_single_leaf_raw(
        bw, gw, hw, jnp.asarray(np.asarray(mw) * govm), num_bins=num_bins)
    histsA, resLA, resRA = search2_update_pallas(
        jnp.asarray(hists_np), h_left, jnp.int32(0), jnp.int32(1),
        jnp.bool_(True), jnp.bool_(True), *scal_args[1:],
        jnp.float32(1.0), jnp.ones(F, bool),
        jnp.full(F, num_bins, jnp.int32), jnp.zeros(F, bool), *lim_args)

    histsB, recB, nlB, res = split_step_window(
        jnp.asarray(hists_np), rec, jnp.int32(0), jnp.int32(n),
        jnp.bool_(True), jnp.int32(f), jnp.int32(thr), jnp.bool_(False),
        jnp.int32(0), jnp.int32(1), scal, meta, F=F, cap=cap, k=k)

    ok = True
    if int(nlA) != int(nlB):
        log(f"  split nleft mismatch: {int(nlA)} vs {int(nlB)}")
        ok = False
    # data rows must match exactly; the mega path additionally stamps
    # the leaf-id row, which partition_window (leaf_row=None) left at 0
    W = rec.shape[0]
    from lightgbm_tpu.ops.record import num_words
    lr = num_words(F, k) + 4
    ra, rb = np.asarray(recA), np.asarray(recB)
    rows = [r for r in range(W) if r != lr]
    if not np.array_equal(ra[rows], rb[rows]):
        log("  split record data rows mismatch")
        ok = False
    d = float(np.abs(np.asarray(histsA) - np.asarray(histsB)).max())
    if d > 2e-2:  # different accumulation grouping on real floats
        log(f"  split hists row diff {d}")
        ok = False
    from lightgbm_tpu.ops.pallas_search import _unpack
    for c, (a, b) in enumerate(
            ((resLA, _unpack(res, 0)), (resRA, _unpack(res, 1)))):
        fa, fb = int(a.feature), int(b.feature)
        if fa != fb:  # float accumulation may flip only exact ties
            log(f"  split child {c} feature mismatch: {fa} vs {fb} "
                f"(gains {float(a.gain):.6g} vs {float(b.gain):.6g})")
            ok = ok and abs(float(a.gain) - float(b.gain)) <= 1e-4 * max(
                1.0, abs(float(a.gain)))
    log(f"split parity: {'OK' if ok else 'FAIL'} "
        f"(nleft={int(nlB)}, hist maxdiff={d:.2e})")
    return ok


def check_writeback(rng) -> bool:
    import jax.numpy as jnp

    from lightgbm_tpu.ops.record import TILE, write_window

    rec = jnp.asarray(
        rng.randint(-2**30, 2**30, (16, 8 * TILE)).astype(np.int32))
    out = jnp.asarray(
        rng.randint(-2**30, 2**30, (16, 2 * TILE)).astype(np.int32))
    ok = True
    for begin in (0, 1, 37, 500, TILE - 1):
        got = np.asarray(write_window(rec, out, jnp.int32(begin), 2 * TILE))
        ref = np.asarray(rec).copy()
        ref[:, begin:begin + 2 * TILE] = np.asarray(out)
        if not np.array_equal(got, ref):
            bad = np.argwhere(got != ref)
            log(f"  writeback MISMATCH at begin={begin}: "
                f"{len(bad)} cells, first {bad[:3].tolist()}")
            ok = False
    log(f"writeback parity: {'OK' if ok else 'FAIL'}")
    return ok


def check_place(rng) -> bool:
    """place_runs (aliased placement kernel) vs the XLA scan-of-DUS
    reference it replaces — the hardware-only path (interpret falls
    back to the reference)."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.record import (
        TILE, bins_per_word, build_record, extract_feature, num_words,
        partition_window, place_runs, round_up, split_step_window)
    from lightgbm_tpu.ops.pallas_search import _pack_meta, _pack_scal

    # the last trial runs with a tiny LGBM_TPU_PLACE_CHUNK so the
    # multi-launch chunk-boundary path (forced adv=1 per launch) is
    # pinned at test size — its unique shape forces a fresh trace with
    # the env value baked in (the knob is read at trace time)
    ok = True
    for trial, (F, n, num_bins, begin_off, frac) in enumerate((
            (9, 5000, 33, 0, 0.5),
            (9, 5000, 33, 777, 0.2),   # unaligned begin, unbalanced
            (9, 5000, 33, 1291, 0.97),  # nearly-all-left
            (5, 2000, 16, 300, 0.0),   # all-right
            (7, 3000, 17, 133, 0.4),   # multi-chunk placement
    )):
        os.environ["LGBM_TPU_PLACE_CHUNK"] = "8" if trial == 4 else "16384"
        bins = rng.randint(0, num_bins, (F, n)).astype(np.uint8)
        g = rng.randn(n).astype(np.float32)
        h = (rng.rand(n) + 0.5).astype(np.float32)
        bag = np.ones(n, np.float32)
        k = bins_per_word(jnp.uint8)
        total = round_up(n + begin_off, TILE) + TILE
        rec = build_record(
            jnp.asarray(np.pad(bins, ((0, 0), (begin_off, 0)))),
            jnp.asarray(np.pad(g, (begin_off, 0))),
            jnp.asarray(np.pad(h, (begin_off, 0))),
            jnp.asarray(np.pad(bag, (begin_off, 0))), total)
        cap = round_up(n, TILE)
        thr = int(num_bins * frac)
        f = 2
        begin = jnp.int32(begin_off)
        fv = extract_feature(rec, jnp.int32(f), begin, cap, k)
        go = (fv <= thr).astype(jnp.int32)
        lr = num_words(F, k) + 4

        # reference: partition_window (scan-of-DUS) with leaf stamping
        recA, nlA = partition_window(
            rec, go, begin, jnp.int32(n), jnp.bool_(True), cap,
            left_leaf=jnp.int32(3), right_leaf=jnp.int32(5),
            leaf_row=lr)
        # kernel path: compacted tiles -> place_runs
        Fp, Bp = round_up(F, 8), round_up(num_bins, 128)
        # slots 3 and 5 are written by the kernel's hists index maps —
        # allocate past them (Pallas does not bounds-check index maps)
        hists = jnp.zeros((7, Fp, 4, Bp), jnp.float32)
        meta = _pack_meta(jnp.ones(F, bool),
                          jnp.full(F, num_bins, jnp.int32),
                          jnp.zeros(F, bool), Fp)
        scal = _pack_scal(*[jnp.float32(x) for x in
                            (1., 0., 1., 9., 0., 1., 9., 1., 1e-3,
                             0., 0., 0.)])
        _, comp, nlB, _, clB, crB, _rp = split_step_window(
            hists, rec, begin, jnp.int32(n), jnp.bool_(True),
            jnp.int32(f), jnp.int32(thr), jnp.bool_(False),
            jnp.int32(3), jnp.int32(5), scal, meta, F=F, cap=cap, k=k,
            return_comp=True)
        recB = place_runs(
            jnp.array(rec), comp, go, begin, jnp.int32(n), nlB,
            jnp.bool_(True), jnp.int32(3), jnp.int32(5), cap=cap,
            leaf_row=lr)
        # kernel-emitted counts must reproduce the go-derived ones
        govm2 = np.asarray(go).astype(np.int64) * (np.arange(cap) < n)
        want_cl = govm2.reshape(-1, TILE).sum(axis=1)
        if not np.array_equal(np.asarray(clB), want_cl):
            log(f"  place trial {trial}: kernel cl mismatch")
            ok = False
        if int(nlA) != int(nlB):
            log(f"  place trial {trial}: nleft {int(nlA)} vs {int(nlB)}")
            ok = False
        ra, rb = np.asarray(recA), np.asarray(recB)
        if not np.array_equal(ra, rb):
            bad = [r for r in range(ra.shape[0])
                   if not np.array_equal(ra[r], rb[r])]
            log(f"  place trial {trial}: record rows differ {bad}")
            ok = False
    log(f"place parity: {'OK' if ok else 'FAIL'}")
    return ok


def main() -> None:
    import jax

    plat = jax.devices()[0].platform
    log(f"platform: {plat}")
    if plat != "tpu":
        log("NOT on TPU — this tool validates Mosaic compilation; "
            "run it in a live-chip window")
        sys.exit(2)
    rng = np.random.RandomState(0)
    results = [check_writeback(rng), check_search(rng), check_split(rng),
               check_place(rng)]
    os.environ.pop("LGBM_TPU_PLACE_CHUNK", None)
    sys.exit(0 if all(results) else 1)


if __name__ == "__main__":
    main()
