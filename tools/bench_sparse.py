"""Sparse O(nnz) histogram crossover measurement (VERDICT r3 item 8).

Times the dense level histogram (histogram_by_leaf / Pallas sorted
kernel) against the CSR O(nnz) path (ops/sparse_hist.py) at fixed
n x F and varying density, and prints the crossover — the density below
which news20-class data should take the sparse path.  The default
Config.sparse_hist_density gate is chosen from this measurement.

    python tools/bench_sparse.py            # real chip if live
    BENCH_PLATFORM=cpu python tools/bench_sparse.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

N = int(float(os.environ.get("SPARSE_ROWS", 200_000)))
F = int(os.environ.get("SPARSE_FEATS", 512))
B = int(os.environ.get("SPARSE_BINS", 32))
L = 16


def main() -> None:
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    import jax.numpy as jnp

    from lightgbm_tpu.ops.histogram import histogram_by_leaf
    from lightgbm_tpu.ops.pallas_histogram import make_sorted_hist_fn
    from lightgbm_tpu.ops.sparse_hist import sparse_histogram_by_leaf

    platform = jax.devices()[0].platform
    print(f"platform={platform} n={N} F={F} B={B} L={L}", file=sys.stderr)
    rng = np.random.RandomState(0)
    leaf_id = jnp.asarray(rng.randint(0, L, N).astype(np.int32))
    g = jnp.asarray(rng.randn(N).astype(np.float32))
    h = jnp.asarray((rng.rand(N) + 0.5).astype(np.float32))
    m = jnp.ones(N, jnp.float32)

    def timeit(fn, *args, reps=5):
        fn(*args).block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        out.block_until_ready()
        return (time.perf_counter() - t0) / reps

    rows = []
    for density in (0.005, 0.01, 0.02, 0.05, 0.1, 0.2):
        nnz = int(N * F * density)
        erow = jnp.asarray(
            np.sort(rng.randint(0, N, nnz)).astype(np.int32))
        ecol = jnp.asarray(rng.randint(0, F, nnz).astype(np.int32))
        ebin = jnp.asarray(rng.randint(1, B, nnz).astype(np.uint8))
        dbins = jnp.zeros(F, jnp.int32)
        # dense matrix holding the same data (default bin 0 elsewhere)
        dense = np.zeros((F, N), np.uint8)
        dense[np.asarray(ecol), np.asarray(erow)] = np.asarray(ebin)
        bins_T = jnp.asarray(dense)

        t_sparse = timeit(
            lambda: sparse_histogram_by_leaf(
                erow, ecol, ebin, dbins, leaf_id, g, h, m,
                num_leaves=L, num_features=F, num_bins=B))
        if platform == "tpu":
            # the production dense path on chip (Pallas sorted kernel);
            # the jnp segment fallback broadcasts [F, n, 3] and OOMs HBM
            # at wide-F shapes
            sorted_fn = make_sorted_hist_fn(B)
            t_dense = timeit(
                lambda: sorted_fn(bins_T, leaf_id, g, h, m, L))
        else:
            t_dense = timeit(
                lambda: histogram_by_leaf(
                    bins_T, leaf_id, g, h, m, num_bins=B, num_leaves=L))
        rows.append({"density": density, "sparse_ms": round(t_sparse * 1e3, 2),
                     "dense_ms": round(t_dense * 1e3, 2),
                     "sparse_wins": bool(t_sparse < t_dense)})
        print(rows[-1], file=sys.stderr)

    cross = next((r["density"] for r in rows if not r["sparse_wins"]), None)
    print(json.dumps({"platform": platform, "rows": rows,
                      "crossover_density": cross}))


if __name__ == "__main__":
    main()
