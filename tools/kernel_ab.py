"""A/B the histogram kernel variants + end-to-end growth modes on TPU.

Run when the chip is reachable:  python tools/kernel_ab.py [rows]

Times, at bench shapes (F=28, B=255, L=255):
  1. sorted level kernel, v1 vs bsub
  2. single-leaf kernel (n/4 and n/16 rows), v1 vs bsub
  3. leafwise + depthwise end-to-end s/tree for the variant selected by
     LGBM_TPU_HIST_KERNEL (read ONCE at import of ops.pallas_histogram
     — jaxlint env-read-at-trace hoist — so EXPORT it before launching
     and run the script once per variant to get both end-to-end
     numbers; a mid-process os.environ flip is ignored)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ROWS = int(float(sys.argv[1])) if len(sys.argv) > 1 else 1_000_000


def t(fn, reps=5):
    import jax

    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1000


def main():
    plat = os.environ.get("BENCH_PLATFORM")
    if not plat and os.environ.get("BENCH_REQUIRE_TPU", "0") != "0":
        # probe BEFORE backend init: a dead tunnel makes jax.devices()
        # hang for the watcher's whole stage timeout otherwise
        from lightgbm_tpu.backend import default_backend_alive, require_tpu_or_row

        if not default_backend_alive(90):
            require_tpu_or_row("none")
            return

    import jax

    if plat:
        jax.config.update("jax_platforms", plat)
    import jax.numpy as jnp

    from lightgbm_tpu.ops.pallas_histogram import (
        histogram_by_leaf_sorted, histogram_single_leaf)

    print("devices:", jax.devices(), flush=True)
    if os.environ.get("BENCH_REQUIRE_TPU", "0") != "0":
        # the pre-init probe above only proves SOME backend answers; if
        # the axon plugin failed fast and jax fell back to CPU, the
        # interpret-mode sweep would burn the whole stage window
        from lightgbm_tpu.backend import require_tpu_or_row

        if not require_tpu_or_row(jax.default_backend()):
            return
    interpret = jax.default_backend() != "tpu"
    rng = np.random.RandomState(0)
    F, B, L = 28, 255, 255
    bins = jnp.asarray(rng.randint(0, B, (F, ROWS)).astype(np.uint8))
    leaf = jnp.asarray(rng.randint(0, 128, ROWS).astype(np.int32))
    g = jnp.asarray(rng.randn(ROWS).astype(np.float32))
    ones = jnp.ones(ROWS, jnp.float32)

    for variant in ("v1", "bsub"):
        try:
            ms = t(lambda: histogram_by_leaf_sorted(
                bins, leaf, g, ones, ones, num_bins=B, num_leaves=L,
                interpret=interpret, variant=variant))
            print(f"sorted level kernel [{variant}]: {ms:.1f} ms", flush=True)
        except Exception as e:
            print(f"sorted level kernel [{variant}] FAILED: "
                  f"{type(e).__name__}: {str(e)[:300]}", flush=True)
        for frac in (4, 16):
            m = ROWS // frac
            for chunk in (512, 1024, 2048):
                try:
                    ms = t(lambda: histogram_single_leaf(
                        bins[:, :m], g[:m], ones[:m], ones[:m], num_bins=B,
                        chunk=chunk, interpret=interpret, variant=variant))
                    print(f"single-leaf n/{frac} chunk={chunk} [{variant}]: "
                          f"{ms:.1f} ms", flush=True)
                except Exception as e:
                    print(f"single-leaf n/{frac} chunk={chunk} [{variant}] "
                          f"FAILED: {type(e).__name__}: {str(e)[:300]}",
                          flush=True)

    # gather-layout A/B: the leafwise smaller-child gather is currently a
    # minor-dim column take of [F, n]; the alternative keeps a row-major
    # copy and gathers rows (then relayouts [cap, F] -> [F, cap]).
    bins_rm = jnp.asarray(np.ascontiguousarray(np.asarray(bins).T))  # [n, F]
    for cap in (ROWS // 4, ROWS // 16):
        idx = jnp.asarray(rng.randint(0, ROWS, cap).astype(np.int32))

        @jax.jit
        def take_cols(i):
            return jnp.take(bins, i, axis=1)

        @jax.jit
        def take_rows_T(i):
            return bins_rm[i].T

        try:
            ms_c = t(lambda: take_cols(idx))
            ms_r = t(lambda: take_rows_T(idx))
            print(f"gather cap={cap}: col-take {ms_c:.2f} ms, "
                  f"row-take+T {ms_r:.2f} ms", flush=True)
        except Exception as e:
            print(f"gather cap={cap} FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # end-to-end growth modes (uses LGBM_TPU_HIST_KERNEL env default).
    # KERNEL_AB_SKIP_E2E=1 stops here: the end-to-end leafwise compile is
    # the giant one (~9 tier bodies; >40 min observed on the tunnel), and
    # the watcher covers end-to-end via the bench stages — the micro
    # numbers above are this tool's unique output.
    if os.environ.get("KERNEL_AB_SKIP_E2E", "0") != "0":
        return
    import bench
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.io.metadata import Metadata
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    X, y = bench.make_data(ROWS)
    for growth in ("leafwise", "depthwise"):
        cfg = Config(objective="binary", num_leaves=255, max_bin=255,
                     learning_rate=0.1, min_data_in_leaf=100,
                     metric=["auc"], tree_growth=growth)
        ds = BinnedDataset.from_matrix(
            X, Metadata(label=y.astype(np.float32)), config=cfg)
        booster = GBDT(cfg, ds, create_objective(cfg, ds.metadata, ds.num_data))
        t0 = time.perf_counter()
        booster.train_one_iter()
        _ = np.asarray(booster._scores[0, :1])
        t_compile = time.perf_counter() - t0
        t0 = time.perf_counter()
        trees = 10
        for _ in range(trees):
            booster.train_one_iter()
        _ = np.asarray(booster._scores)
        t_tree = (time.perf_counter() - t0) / trees
        auc = booster.eval_at(0).get("auc", float("nan"))
        print(f"{growth} [{os.environ.get('LGBM_TPU_HIST_KERNEL', 'v1')}]: "
              f"compile+1st {t_compile:.1f}s, {t_tree*1000:.0f} ms/tree, "
              f"AUC {auc:.4f}", flush=True)


if __name__ == "__main__":
    main()
