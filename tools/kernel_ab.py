"""A/B the partition-routing strategies, histogram kernel variants and
end-to-end growth modes.

Routing A/B (runs first, works on CPU AND TPU — the parity and FLOP
halves of ISSUE 12's acceptance):

  python tools/kernel_ab.py --routing-only [rows]

asserts the ``onehot`` and ``prefix`` partition compactions produce
BITWISE-IDENTICAL records (partition_window + the fused split step,
in one process via the kernels' ``routing=`` static arg — this is why
the knob is an argument and not only the LGBM_TPU_REC_ROUTING env),
reports the HLO-cost-analysis FLOP ratio and wall-clock per routing,
and writes the artifact to ``.bench/kernel_ab_routing.json``
(atomic writer, PR 11 conventions).

Histogram/e2e A/B (TPU; the original tool):  python tools/kernel_ab.py [rows]

Times, at bench shapes (F=28, B=255, L=255):
  1. sorted level kernel, v1 vs bsub
  2. single-leaf kernel (n/4 and n/16 rows), v1 vs bsub
  3. leafwise + depthwise end-to-end s/tree for the variant selected by
     LGBM_TPU_HIST_KERNEL (read ONCE at import of ops.pallas_histogram
     — jaxlint env-read-at-trace hoist — so EXPORT it before launching
     and run the script once per variant to get both end-to-end
     numbers; a mid-process os.environ flip is ignored)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

_ARGS = [a for a in sys.argv[1:] if not a.startswith("-")]
_FLAGS = {a for a in sys.argv[1:] if a.startswith("-")}
ROWS = int(float(_ARGS[0])) if _ARGS else 1_000_000


def t(fn, reps=5):
    import jax

    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1000


def routing_ab(rows):
    """A/B the two partition-routing strategies in ONE process: bitwise
    parity of partition_window and the fused split step, HLO FLOPs per
    routing (cost analysis of the interpret lowering — the dots vs the
    compress network as real XLA ops), and wall-clock per routing on
    the current backend.  Writes .bench/kernel_ab_routing.json."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops import record as R
    from lightgbm_tpu.resilience import atomic_write_json

    interpret = jax.default_backend() != "tpu"
    T = R.TILE
    F, B = 28, 255
    k = R.bins_per_word(jnp.uint8)
    n = R.round_up(min(rows, 262_144) if interpret else rows, T)
    rng = np.random.RandomState(0)
    bins = rng.randint(0, B, (F, n)).astype(np.uint8)
    rec = R.build_record(
        jnp.asarray(bins), jnp.asarray(rng.randn(n).astype(np.float32)),
        jnp.ones(n, jnp.float32),
        jnp.asarray((rng.rand(n) < 0.8).astype(np.float32)),  # bag word
        n + T)
    leaf_row = R.num_words(F, k) + 4
    cap = n
    fv = R.extract_feature(rec, jnp.int32(2), jnp.int32(0), cap, k)
    go = (fv <= 100).astype(jnp.int32)
    pcnt = jnp.int32(n - 37)  # ragged: invalid tail rides the window
    args = (rec, go, jnp.int32(0), pcnt, jnp.bool_(True))
    kw = dict(cap=cap, left_leaf=jnp.int32(0), right_leaf=jnp.int32(1),
              leaf_row=leaf_row, interpret=interpret)

    out = {"tool": "kernel_ab.routing_ab", "rows": int(n),
           "tile": int(T), "backend": jax.default_backend(),
           "default_routing": R.ROUTING,
           "parity": {}, "flops": {}, "wall_ms": {}}

    recs = {}
    for routing in ("onehot", "prefix"):
        r2, nl = R.partition_window(*args, routing=routing, **kw)
        jax.block_until_ready(r2)
        recs[routing] = (np.asarray(r2).tobytes(), int(nl))
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            r2, nl = R.partition_window(*args, routing=routing, **kw)
        jax.block_until_ready(r2)
        out["wall_ms"][routing] = round(
            (time.perf_counter() - t0) / reps * 1000, 3)

        def _flops(lowered):
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, list):
                ca = ca[0]
            return float(ca.get("flops", 0.0))

        # whole-program FLOPs at the A/B window (context: the interpret
        # grid is a while loop, so the kernel body counts ONCE and the
        # surrounding O(n) work dilutes the ratio as n grows) ...
        out["flops"].setdefault("program", {})[routing] = _flops(
            R.partition_window.lower(
                *args, routing=routing, **dict(kw, interpret=True)))
        # ... and the ROUTING-KERNEL FLOPs at a one-TILE window (the
        # hlo_audit pinned shape): the acceptance-criterion number —
        # per-tile routing work is what the strategies differ in
        out["flops"].setdefault("kernel_one_tile", {})[routing] = _flops(
            R.partition_window.lower(
                rec, go[:T], jnp.int32(0), jnp.int32(T),
                jnp.bool_(True), routing=routing,
                **dict(kw, cap=T, interpret=True)))
    bitwise = (recs["onehot"][0] == recs["prefix"][0]
               and recs["onehot"][1] == recs["prefix"][1])
    out["parity"]["partition_window_bitwise"] = bitwise
    for key in ("program", "kernel_one_tile"):
        d = out["flops"][key]
        d["onehot_over_prefix"] = round(
            d["onehot"] / max(d["prefix"], 1.0), 2)

    # fused split step: all four outputs must agree byte-for-byte
    # (fresh inputs per routing — hists is donated)
    from lightgbm_tpu.analysis.hlo_audit import _split_step_inputs

    ss = {}
    for routing in ("onehot", "prefix"):
        srec, hists, scal_f, meta, s, scap, sk = _split_step_inputs()
        o = R.split_step_window(
            hists, srec, s["begin"], s["pcnt"], s["do_split"], s["f"],
            s["thr"], s["is_cat"], s["parent_slot"], s["new_slot"],
            scal_f, meta, F=4, cap=scap, k=sk, interpret=interpret,
            routing=routing)
        ss[routing] = b"".join(np.asarray(x).tobytes() for x in o)
    out["parity"]["split_step_window_bitwise"] = ss["onehot"] == ss["prefix"]

    print(f"routing A/B (n={n}, TILE={T}, backend="
          f"{out['backend']}):", flush=True)
    print(f"  partition_window bitwise-identical: "
          f"{out['parity']['partition_window_bitwise']}", flush=True)
    print(f"  split_step_window bitwise-identical: "
          f"{out['parity']['split_step_window_bitwise']}", flush=True)
    for key in ("kernel_one_tile", "program"):
        d = out["flops"][key]
        print(f"  HLO flops [{key}]: onehot {d['onehot']:.3e}, prefix "
              f"{d['prefix']:.3e} ({d['onehot_over_prefix']}x)",
              flush=True)
    print(f"  wall ms/partition: {out['wall_ms']}", flush=True)

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".bench", "kernel_ab_routing.json")
    atomic_write_json(path, out)
    print(f"  wrote {path}", flush=True)
    assert bitwise and out["parity"]["split_step_window_bitwise"], (
        "routing parity FAILED — do not ship")
    return out


def main():
    plat = os.environ.get("BENCH_PLATFORM")
    if not plat and os.environ.get("BENCH_REQUIRE_TPU", "0") != "0":
        # probe BEFORE backend init: a dead tunnel makes jax.devices()
        # hang for the watcher's whole stage timeout otherwise
        from lightgbm_tpu.backend import default_backend_alive, require_tpu_or_row

        if not default_backend_alive(90):
            require_tpu_or_row("none")
            return

    import jax

    if plat:
        jax.config.update("jax_platforms", plat)
    import jax.numpy as jnp

    from lightgbm_tpu.ops.pallas_histogram import (
        histogram_by_leaf_sorted, histogram_single_leaf)

    print("devices:", jax.devices(), flush=True)
    if os.environ.get("BENCH_REQUIRE_TPU", "0") != "0":
        # the pre-init probe above only proves SOME backend answers; if
        # the axon plugin failed fast and jax fell back to CPU, the
        # interpret-mode sweep would burn the whole stage window
        from lightgbm_tpu.backend import require_tpu_or_row

        if not require_tpu_or_row(jax.default_backend()):
            return
    interpret = jax.default_backend() != "tpu"

    # partition-routing A/B first: cheap, runs on any backend, and its
    # parity assert is the thing that must never regress silently.
    # Guarded like every other section — if Mosaic rejects the prefix
    # kernel on a real chip (the documented risk; routing="prefix" is
    # explicit here, so the LGBM_TPU_REC_ROUTING=onehot escape hatch
    # cannot skip it), the histogram/e2e A/B below must still get its
    # chip window.  --routing-only keeps the loud failure.
    try:
        routing_ab(ROWS)
        routing_ok = True
    except Exception as e:
        print(f"routing A/B FAILED: {type(e).__name__}: {str(e)[:300]}",
              flush=True)
        routing_ok = False
    if "--routing-only" in _FLAGS:
        if not routing_ok:
            sys.exit(1)
        return

    rng = np.random.RandomState(0)
    F, B, L = 28, 255, 255
    bins = jnp.asarray(rng.randint(0, B, (F, ROWS)).astype(np.uint8))
    leaf = jnp.asarray(rng.randint(0, 128, ROWS).astype(np.int32))
    g = jnp.asarray(rng.randn(ROWS).astype(np.float32))
    ones = jnp.ones(ROWS, jnp.float32)

    for variant in ("v1", "bsub"):
        try:
            ms = t(lambda: histogram_by_leaf_sorted(
                bins, leaf, g, ones, ones, num_bins=B, num_leaves=L,
                interpret=interpret, variant=variant))
            print(f"sorted level kernel [{variant}]: {ms:.1f} ms", flush=True)
        except Exception as e:
            print(f"sorted level kernel [{variant}] FAILED: "
                  f"{type(e).__name__}: {str(e)[:300]}", flush=True)
        for frac in (4, 16):
            m = ROWS // frac
            for chunk in (512, 1024, 2048):
                try:
                    ms = t(lambda: histogram_single_leaf(
                        bins[:, :m], g[:m], ones[:m], ones[:m], num_bins=B,
                        chunk=chunk, interpret=interpret, variant=variant))
                    print(f"single-leaf n/{frac} chunk={chunk} [{variant}]: "
                          f"{ms:.1f} ms", flush=True)
                except Exception as e:
                    print(f"single-leaf n/{frac} chunk={chunk} [{variant}] "
                          f"FAILED: {type(e).__name__}: {str(e)[:300]}",
                          flush=True)

    # gather-layout A/B: the leafwise smaller-child gather is currently a
    # minor-dim column take of [F, n]; the alternative keeps a row-major
    # copy and gathers rows (then relayouts [cap, F] -> [F, cap]).
    bins_rm = jnp.asarray(np.ascontiguousarray(np.asarray(bins).T))  # [n, F]
    for cap in (ROWS // 4, ROWS // 16):
        idx = jnp.asarray(rng.randint(0, ROWS, cap).astype(np.int32))

        @jax.jit
        def take_cols(i):
            return jnp.take(bins, i, axis=1)

        @jax.jit
        def take_rows_T(i):
            return bins_rm[i].T

        try:
            ms_c = t(lambda: take_cols(idx))
            ms_r = t(lambda: take_rows_T(idx))
            print(f"gather cap={cap}: col-take {ms_c:.2f} ms, "
                  f"row-take+T {ms_r:.2f} ms", flush=True)
        except Exception as e:
            print(f"gather cap={cap} FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # end-to-end growth modes (uses LGBM_TPU_HIST_KERNEL env default).
    # KERNEL_AB_SKIP_E2E=1 stops here: the end-to-end leafwise compile is
    # the giant one (~9 tier bodies; >40 min observed on the tunnel), and
    # the watcher covers end-to-end via the bench stages — the micro
    # numbers above are this tool's unique output.
    if os.environ.get("KERNEL_AB_SKIP_E2E", "0") != "0":
        return
    import bench
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.io.metadata import Metadata
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    X, y = bench.make_data(ROWS)
    for growth in ("leafwise", "depthwise"):
        cfg = Config(objective="binary", num_leaves=255, max_bin=255,
                     learning_rate=0.1, min_data_in_leaf=100,
                     metric=["auc"], tree_growth=growth)
        ds = BinnedDataset.from_matrix(
            X, Metadata(label=y.astype(np.float32)), config=cfg)
        booster = GBDT(cfg, ds, create_objective(cfg, ds.metadata, ds.num_data))
        t0 = time.perf_counter()
        booster.train_one_iter()
        _ = np.asarray(booster._scores[0, :1])
        t_compile = time.perf_counter() - t0
        t0 = time.perf_counter()
        trees = 10
        for _ in range(trees):
            booster.train_one_iter()
        _ = np.asarray(booster._scores)
        t_tree = (time.perf_counter() - t0) / trees
        auc = booster.eval_at(0).get("auc", float("nan"))
        print(f"{growth} [{os.environ.get('LGBM_TPU_HIST_KERNEL', 'v1')}]: "
              f"compile+1st {t_compile:.1f}s, {t_tree*1000:.0f} ms/tree, "
              f"AUC {auc:.4f}", flush=True)


if __name__ == "__main__":
    main()
