#!/usr/bin/env python
"""benchdiff: compare two bench results and flag regressions.

The round-5 failure mode this tool ends: BENCH_r05 (0.4442 s/tree,
vs_baseline 0.71) was committed next to BENCH_r04 (0.3713 / 1.087) and
nobody diffed them.  ``benchdiff`` normalizes any two result artifacts,
compares headline + phases + compile hygiene against thresholds, and
prints the driver-config bench row ROADMAP item 1 requires in any
perf-motivated serial.py/record.py commit.

Accepted input formats (auto-detected per file):

* driver BENCH artifacts  (``BENCH_r0N.json`` — ``{"parsed": {...}}``)
* raw bench.py rows       (``{"metric": ..., "value": ...}``)
* run manifests           (``*.manifest.json`` — obs.manifest v1; the
  headline comes from ``result``, phases from ``phases``)
* multichip artifacts     (``lightgbm-tpu/multichip-bench/v1`` from the
  8-process dryrun / a real multi-chip run, obs/dist.py): diffs the
  headline under the usual threshold plus a SKEW-REGRESSION gate — the
  per-span / per-collective cross-rank skews (max−min seconds) must not
  grow past the phase threshold above an absolute floor, so a run that
  stays flat in aggregate but develops a straggling rank is flagged;
  a changed collective census (per-op counts) is warned about.  World
  sizes must match (exit 2 otherwise — 4-rank skew and 8-rank skew are
  not comparable).
* serving bench artifacts (``.bench/serving_*.json`` —
  ``lightgbm-tpu/serving-bench/v1`` from tools/bench_serving.py):
  online mode diffs p50 (headline threshold) / p99 (phase threshold) /
  throughput / error-rate, plus PER-STAGE p50s (queue_wait / pad /
  device / scatter, from the request-tracing breakdown) under the same
  +25% per-phase rule training runs get — a stage can no longer
  regress 3x while the headline hides it in noise.  Batch mode diffs
  file-to-file seconds.  Serving and training artifacts are never
  cross-compared (exit 2).
* serving fleet artifacts (``.bench/serving_fleet.json`` —
  ``lightgbm-tpu/serving-fleet/v1`` from ``bench_serving.py
  --overload``): the headline is ACCEPTED p99 — the latency the
  admission layer protects by shedding — gated at the phase threshold;
  any failed request is a regression outright (overload must shed,
  never fail), as is a leaked queue bound or a dead dispatcher; the
  shed rate is only judged at ~flat offered load (shedding more
  because more was offered is the mechanism working, not breaking),
  where growth past an absolute floor plus the phase threshold is a
  protection regression.  Fleet artifacts are never cross-compared
  with any other kind (exit 2).
* train fleet artifacts   (``.bench/train_fleet.json`` —
  ``lightgbm-tpu/train-fleet/v1`` from ``task=train_fleet`` /
  ``tools/chaos.py rank_kill_midtrain``, resilience/gang.py): the
  headline is MEAN TIME TO RECOVER — detection of a rank death/hang to
  the reformed gang's last ready handshake — gated at the phase
  threshold (recovery includes jittered backoff, so it is noisier than
  a steady-state latency) and only when BOTH runs actually recovered
  from something; gates that are never perf tradeoffs: any failed
  iteration (the run ended short of its target) is a regression
  outright, as is an exhausted restart budget; lost iterations growing
  at the same barrier cadence is a rollback-quality regression.  World
  shapes must match (exit 2 — recovery across different rank counts is
  not comparable), and train-fleet artifacts are never cross-compared
  with any other kind (exit 2).
* forest bench artifacts  (``.bench/forest_sweep.json`` —
  ``lightgbm-tpu/forest-bench/v1`` from tools/bench_forest.py):
  headline is the batched forest wall (ONE program advancing all N
  models), diffed under the headline threshold; the
  batched-vs-sequential speedup dropping past the headline threshold
  is a regression even when the batched wall itself stays flat (the
  sequential side got faster and batching stopped paying); a batched
  run whose per-model parity hashes no longer match its own sequential
  replay (``parity_ok`` false) is flagged as a correctness regression,
  and ``grow_traces`` growing means the one-trace contract broke
  (trace-per-model came back).  Model counts must match (exit 2 —
  an 8-model sweep and a 16-model sweep are not comparable), and
  forest artifacts are never cross-compared with any other kind
  (exit 2).

Usage:
    python tools/benchdiff.py OLD NEW [--threshold PCT]
        [--phase-threshold PCT] [--json OUT]

Exit codes (diff semantics): 0 = no regression, 1 = regression flagged,
2 = usage/parse error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

# default thresholds (percent).  Headline: the acceptance bar is
# "+>=15% s/tree is a regression"; phases get more slack because
# per-phase attribution carries trace sampling noise.
HEADLINE_PCT = 15.0
PHASE_PCT = 25.0
AUC_ABS = 0.002  # an AUC drop is a correctness smell, not a perf one

MANIFEST_SCHEMA = "lightgbm-tpu/run-manifest/v1"
SERVING_SCHEMA = "lightgbm-tpu/serving-bench/v1"
MULTICHIP_SCHEMA = "lightgbm-tpu/multichip-bench/v1"
FOREST_SCHEMA = "lightgbm-tpu/forest-bench/v1"
FLEET_SCHEMA = "lightgbm-tpu/serving-fleet/v1"
TRAIN_FLEET_SCHEMA = "lightgbm-tpu/train-fleet/v1"
# shed-rate noise floor (absolute fraction of offered requests): below
# this, a shed-rate delta at flat load is sampling noise, not a signal
FLEET_SHED_ABS = 0.02
# cross-rank skew gate: a skew below this absolute floor is scheduling
# noise on any backend — relative growth only matters above it
SKEW_ABS_FLOOR_S = 0.02
# serving error-rate discipline: a regression needs BOTH an absolute
# rise above this floor (noise guard; also covers a 0 baseline) and —
# when the baseline had errors — a relative rise past the headline
# threshold
ERROR_RATE_ABS = 0.001


def _load(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def _normalize_serving(raw: dict, rec: dict) -> dict:
    """Serving artifacts: the headline is p50 latency (online) or
    file-to-file seconds (batch); p99/throughput/error-rate ride in
    ``aux`` for the serving-specific diff."""
    s = dict(raw.get("serving") or {})
    rec["kind"] = "serving"
    rec["mode"] = s.get("mode", "online")
    if rec["mode"] == "batch":
        rec["value"] = s.get("file_to_file_s")
        rec["unit"] = "s file-to-file"
    else:
        rec["value"] = s.get("p50_ms")
        rec["unit"] = "ms p50"
    rec["aux"] = {k: s.get(k) for k in
                  ("p99_ms", "throughput_rps", "rows_per_s", "error_rate",
                   "requests", "errors", "unpipelined_s", "speedup")
                  if s.get(k) is not None}
    rec["stages"] = dict(s.get("stages") or {})
    rec["shape"] = raw.get("shape") or {}
    rec["knobs"] = raw.get("knobs") or {}
    if rec.get("value") in (None, 0, 0.0):
        raise ValueError(
            f"{rec['path']}: serving artifact has no usable headline "
            f"({'file_to_file_s' if rec['mode'] == 'batch' else 'p50_ms'})")
    return rec


def _normalize_forest(raw: dict, rec: dict) -> dict:
    """Forest-bench artifacts (tools/bench_forest.py): headline is the
    batched wall — the one dispatch-per-round program advancing all N
    models; the sequential wall / speedup / parity hashes / trace
    counters ride in ``aux`` for the forest-specific diff."""
    f = dict(raw.get("forest") or {})
    rec["kind"] = "forest"
    rec["num_models"] = f.get("num_models")
    rec["value"] = f.get("batched_wall_s")
    rec["unit"] = "s batched-wall"
    rec["aux"] = {k: f.get(k) for k in
                  ("sequential_wall_s", "speedup", "rounds", "rows",
                   "features", "num_class", "grow_traces",
                   "forest_dispatches", "forest_batched_trees")
                  if f.get(k) is not None}
    rec["parity"] = dict(f.get("parity") or {})
    rec["parity_ok"] = f.get("parity_ok")
    rec["shape"] = {k: f.get(k) for k in
                    ("rows", "features", "num_class", "rounds")}
    rec["knobs"] = raw.get("knobs") or {}
    if rec.get("value") in (None, 0, 0.0):
        raise ValueError(
            f"{rec['path']}: forest artifact has no usable headline "
            "(forest.batched_wall_s)")
    return rec


def _normalize_fleet(raw: dict, rec: dict) -> dict:
    """Serving-fleet overload artifacts (tools/bench_serving.py
    --overload): headline is the ACCEPTED p99 — the latency the
    admission layer protects by shedding; offered/accepted rates, the
    shed split, and the failure count ride in ``aux`` for the
    fleet-specific gates."""
    f = dict(raw.get("fleet") or {})
    rec["kind"] = "fleet"
    rec["value"] = f.get("accepted_p99_ms")
    rec["unit"] = "ms accepted-p99"
    rec["aux"] = {k: f.get(k) for k in
                  ("sustainable_rps", "offered_rps", "accepted_rps",
                   "offered", "accepted", "completed", "shed_total",
                   "shed_rate", "failed", "accepted_p50_ms",
                   "deadline_ms", "max_queue_rows",
                   "max_pending_rows_observed", "queue_bound_held",
                   "dispatcher_alive", "overload_factor")
                  if f.get(k) is not None}
    rec["shed"] = dict(f.get("shed") or {})
    rec["shape"] = raw.get("shape") or {}
    if rec.get("value") in (None, 0, 0.0):
        raise ValueError(
            f"{rec['path']}: fleet artifact has no usable headline "
            "(fleet.accepted_p99_ms)")
    return rec


def _normalize_train_fleet(raw: dict, rec: dict) -> dict:
    """Train-fleet recovery artifacts (resilience/gang.py): headline is
    mean-time-to-recover; the recovery ladder's tallies (restarts,
    shrinks, lost/failed iterations, budget spend) ride in ``aux`` for
    the train-fleet-specific gates.  Unlike every other kind an
    mttr_s of 0 is a VALID headline — a run that never needed to
    recover (the uninterrupted baseline) is the best possible result,
    not an unusable record."""
    f = dict(raw.get("train_fleet") or {})
    rec["kind"] = "train_fleet"
    rec["value"] = float(f.get("mttr_s") or 0.0)
    rec["unit"] = "s mttr"
    rec["aux"] = {k: f.get(k) for k in
                  ("world_size_start", "world_size_end", "restarts",
                   "shrinks", "rank_deaths", "rank_hangs", "recoveries",
                   "lost_iterations", "failed_iterations",
                   "target_iterations", "budget_spent",
                   "budget_exhausted", "preempted", "final_barrier",
                   "barriers_committed", "exit_code", "wall_s")
                  if f.get(k) is not None}
    rec["recovery_timeline"] = list(f.get("recovery_timeline") or [])
    rec["shape"] = raw.get("shape") or {}
    rec["counters"] = raw.get("counters") or {}
    return rec


def _normalize_multichip(raw: dict, rec: dict) -> dict:
    """Multichip artifacts: headline from ``result.value``; the skew
    tables (span + reservoir, already ``{name: {max_minus_min_s, ...}}``)
    ride flattened for the skew-regression gate; per-op collective
    counts ride for the census warning."""
    rec["kind"] = "multichip"
    rec["world"] = raw.get("world")
    row = dict(raw.get("result") or {})
    rec["value"] = row.get("value")
    rec["unit"] = row.get("unit", "s")
    skew = raw.get("skew") or {}
    flat = {}
    for group in ("spans", "reservoirs"):
        for name, sk in (skew.get(group) or {}).items():
            flat[name] = sk
    rec["skew"] = flat
    counters = (raw.get("merged") or {}).get("counters") or {}
    rec["collective_census"] = {
        k: counters[k] for k in sorted(counters)
        if k.startswith(("collective_ops.op.", "collective_site."))}
    rec["stragglers"] = raw.get("stragglers") or []
    # per-rank device-memory peaks (obs/memory.py): the artifact-level
    # hbm_peak_bytes is the worst rank — the one the next OOM kills
    rank_hbm = {}
    for r in raw.get("ranks") or []:
        if r.get("hbm_peak_bytes"):
            rank_hbm[r.get("process_index")] = int(r["hbm_peak_bytes"])
    extra_hbm = (raw.get("extra") or {}).get("hbm_peak_bytes")
    peak = max(rank_hbm.values(), default=0) or int(extra_hbm or 0)
    if peak:
        rec["hbm_peak_bytes"] = peak
        rec["rank_hbm_peak_bytes"] = rank_hbm
    if rec.get("value") in (None, 0, 0.0):
        raise ValueError(
            f"{rec['path']}: multichip artifact has no usable headline "
            "(result.value)")
    return rec


def normalize(path: str) -> dict:
    """One record shape for every accepted input format:
    ``{label, value, unit, vs_baseline, auc..., phases, compile...}``."""
    raw = _load(path)
    rec: dict = {"label": os.path.basename(path), "path": path,
                 "phases": {}, "sha": None, "kind": "training"}
    if raw.get("schema") == TRAIN_FLEET_SCHEMA:
        return _normalize_train_fleet(raw, rec)
    if raw.get("schema") == FLEET_SCHEMA:
        return _normalize_fleet(raw, rec)
    if raw.get("schema") == FOREST_SCHEMA:
        return _normalize_forest(raw, rec)
    if raw.get("schema") == MULTICHIP_SCHEMA:
        return _normalize_multichip(raw, rec)
    if raw.get("schema") == SERVING_SCHEMA or "serving" in raw:
        return _normalize_serving(raw, rec)
    if raw.get("schema") == MANIFEST_SCHEMA:
        row = dict(raw.get("result") or {})
        rec["phases"] = dict(raw.get("phases") or {})
        rec["sha"] = (raw.get("git") or {}).get("sha")
        rec["per_tree"] = raw.get("per_tree") or {}
        rec["warmup"] = raw.get("warmup") or {}
        # memory section (obs/memory.py manifest_memory_section):
        # hbm peak is gateable like the headline
        hbm = (raw.get("memory") or {}).get("hbm") or {}
        if hbm.get("hbm_peak_bytes"):
            rec["hbm_peak_bytes"] = int(hbm["hbm_peak_bytes"])
        # northstar manifests carry the headline under another key
        if "value" not in row and "steady_sec_per_tree" in row:
            row["value"] = row["steady_sec_per_tree"]
            row.setdefault("unit", "s/tree")
        # cli.train manifests record wall + tree count: synthesize the
        # s/tree headline so any two run manifests really are diffable
        # (README's promise)
        if "value" not in row and row.get("train_wall_s") \
                and row.get("num_trees"):
            row["value"] = float(row["train_wall_s"]) / row["num_trees"]
            row.setdefault("unit", "s/tree (wall, incl. compile)")
    elif "parsed" in raw:  # driver BENCH artifact
        row = dict(raw["parsed"] or {})
    else:  # raw bench.py row
        row = dict(raw)
    for k in ("metric", "value", "unit", "vs_baseline", "platform",
              "growth", "train_auc", "valid_auc", "knobs", "error",
              "warmup_iters", "warm_trees_discarded", "compile_stable",
              "compiles_warmup", "compiles_timed", "timed_trees",
              "hbm_peak_bytes"):
        if k in row:
            rec[k] = row[k]
    if "phases" in row and not rec["phases"]:
        rec["phases"] = dict(row["phases"] or {})
    if rec.get("value") in (None, 0, 0.0) and "error" not in row:
        # a zero headline is an unusable record, not a 100% improvement
        raise ValueError(f"{path}: no usable headline value in {row}")
    return rec


def _pct(old: float, new: float) -> float:
    return (new - old) / old * 100.0 if old else float("inf")


def _diff_hbm(old: dict, new: dict, regressions: list, warnings: list,
              improvements: list, headline_pct: float) -> None:
    """Device-memory gate, shared by training and multichip diffs: at
    the same shape, ``hbm_peak_bytes`` growing past the headline
    threshold is a regression EVEN when the time headline stays flat —
    a +15% peak at 100M rows is the next OOM (ROADMAP items 3/4), and
    time gates alone would wave it through."""
    oh = int(old.get("hbm_peak_bytes") or 0)
    nh = int(new.get("hbm_peak_bytes") or 0)
    if oh <= 0 and nh <= 0:
        return
    if oh <= 0 or nh <= 0:
        side = "old" if nh else "new"
        warnings.append(
            f"hbm_peak_bytes present only in the {side} artifact — "
            "memory coverage changed between the two runs")
        return
    d = _pct(oh, nh)
    if d >= headline_pct:
        regressions.append(
            f"hbm_peak_bytes {oh} -> {nh} (+{d:.1f}%, threshold "
            f"+{headline_pct:.0f}%) — device-memory regression at "
            "same shape")
    elif d <= -headline_pct:
        improvements.append(f"hbm_peak_bytes {oh} -> {nh} ({d:.1f}%)")


def diff_serving(old: dict, new: dict, headline_pct: float = HEADLINE_PCT,
                 phase_pct: float = PHASE_PCT) -> dict:
    """Serving-artifact comparison under the same threshold discipline
    as training: headline (p50 / file-to-file) +headline_pct is a
    regression, p99 gets the looser phase threshold (tail latency is
    noisier), a throughput drop past the headline threshold regresses,
    and an error-rate rise is judged by ERROR_RATE_ABS + the relative
    headline threshold."""
    regressions, warnings, improvements = [], [], []
    if old.get("mode") != new.get("mode"):
        raise ValueError(
            f"serving modes differ (old: {old.get('mode')}, new: "
            f"{new.get('mode')}) — online and batch artifacts are not "
            "comparable")
    unit = new.get("unit", "")
    ov, nv = float(old["value"]), float(new["value"])
    head = _pct(ov, nv)
    headline = {"old": ov, "new": nv, "unit": unit,
                "delta_pct": round(head, 1)}
    if head >= headline_pct:
        regressions.append(
            f"headline {unit} {ov:.4g} -> {nv:.4g} (+{head:.1f}%, "
            f"threshold +{headline_pct:.0f}%)")
    elif head <= -headline_pct:
        improvements.append(
            f"headline {unit} {ov:.4g} -> {nv:.4g} ({head:.1f}%)")

    oa, na = old.get("aux") or {}, new.get("aux") or {}
    for key, thresh, lower_is_better in (
            ("p99_ms", phase_pct, True),
            ("throughput_rps", headline_pct, False),
            ("rows_per_s", headline_pct, False)):
        if oa.get(key) and na.get(key):
            d = _pct(float(oa[key]), float(na[key]))
            worse = d >= thresh if lower_is_better else d <= -thresh
            better = d <= -thresh if lower_is_better else d >= thresh
            if worse:
                regressions.append(
                    f"{key} {oa[key]:.4g} -> {na[key]:.4g} "
                    f"({d:+.1f}%, threshold {thresh:.0f}%)")
            elif better:
                improvements.append(
                    f"{key} {oa[key]:.4g} -> {na[key]:.4g} ({d:+.1f}%)")
    # per-stage regressions (request-tracing breakdown): same
    # discipline as training phases — +phase_pct on a stage's p50 is a
    # regression even when the headline stays flat (four small stages
    # can hide one 3x stage inside headline noise), a stage present on
    # only one side is reported, never silently dropped
    ost, nst = old.get("stages") or {}, new.get("stages") or {}
    if ost or nst:
        for st in sorted(set(ost) ^ set(nst)):
            side = "old" if st in ost else "new"
            warnings.append(
                f"stage '{st}' present only in the {side} artifact — "
                "tracing coverage changed between the two runs")
        for st in sorted(set(ost) & set(nst)):
            o = float((ost[st] or {}).get("p50_ms") or 0.0)
            n = float((nst[st] or {}).get("p50_ms") or 0.0)
            if o <= 0 or n <= 0:
                if max(o, n) > 0.05:
                    warnings.append(
                        f"stage '{st}' p50 {o:.4g} -> {n:.4g} ms (no "
                        "baseline to diff against)")
                continue
            d = _pct(o, n)
            if d >= phase_pct:
                regressions.append(
                    f"stage '{st}' p50 {o:.4g} -> {n:.4g} ms "
                    f"(+{d:.1f}%, threshold +{phase_pct:.0f}%)")
            elif d <= -phase_pct:
                improvements.append(
                    f"stage '{st}' p50 {o:.4g} -> {n:.4g} ms ({d:.1f}%)")
    elif old.get("mode") == "online":
        warnings.append("no per-stage breakdown on either side "
                        "(re-run tools/bench_serving.py with tracing on)")

    oe = float(oa.get("error_rate") or 0.0)
    ne = float(na.get("error_rate") or 0.0)
    if ne > oe + ERROR_RATE_ABS and (
            oe == 0 or _pct(oe, ne) >= headline_pct):
        regressions.append(
            f"error_rate {oe:.4f} -> {ne:.4f} — serving errors are a "
            "correctness regression, not a perf tradeoff")
    elif oe > ne + ERROR_RATE_ABS:
        improvements.append(f"error_rate {oe:.4f} -> {ne:.4f}")

    os_, ns = old.get("shape") or {}, new.get("shape") or {}
    if os_ and ns and os_ != ns:
        warnings.append(
            f"load shapes differ (old: {os_}, new: {ns}) — comparison "
            "may not be apples-to-apples")
    return {"headline": headline, "regressions": regressions,
            "warnings": warnings, "improvements": improvements}


def diff_fleet(old: dict, new: dict,
               headline_pct: float = HEADLINE_PCT,
               phase_pct: float = PHASE_PCT) -> dict:
    """Serving-fleet overload comparison.  The headline is accepted-p99
    gated at ``phase_pct`` (tail latency at deliberate saturation is
    noisier than a steady-state p99, so it gets the looser phase
    threshold).  Gates that are never perf tradeoffs: any failed
    request is a regression outright (overload must shed with a typed
    status, never fail), as is a queue that leaked past its row bound
    or a dispatcher that died.  The shed rate is only judged when the
    offered load is ~flat (within ``headline_pct``): shedding more
    because MORE was offered is the admission layer working; shedding
    more at the SAME offered load means the service got less able to
    absorb the same demand."""
    regressions, warnings, improvements = [], [], []
    unit = new.get("unit", "ms accepted-p99")
    ov, nv = float(old["value"]), float(new["value"])
    head = _pct(ov, nv)
    headline = {"old": ov, "new": nv, "unit": unit,
                "delta_pct": round(head, 1)}
    if head >= phase_pct:
        regressions.append(
            f"accepted p99 {ov:.4g} -> {nv:.4g} ms (+{head:.1f}%, "
            f"threshold +{phase_pct:.0f}%) — the latency shedding is "
            "supposed to protect")
    elif head <= -phase_pct:
        improvements.append(
            f"accepted p99 {ov:.4g} -> {nv:.4g} ms ({head:.1f}%)")

    oa, na = old.get("aux") or {}, new.get("aux") or {}
    # correctness gates first: these are never perf tradeoffs
    if int(na.get("failed") or 0) > 0:
        regressions.append(
            f"NEW run FAILED {na['failed']} request(s) — an overloaded "
            "fleet must shed with a typed status, never fail")
    if na.get("queue_bound_held") is False:
        regressions.append(
            "NEW run's queue leaked past its row bound "
            f"(observed {na.get('max_pending_rows_observed')} > "
            f"{na.get('max_queue_rows')} rows) — admission control is "
            "not actually bounding memory")
    if na.get("dispatcher_alive") is False:
        regressions.append(
            "NEW run's dispatcher died under overload — shedding must "
            "leave the serving loop standing")

    oo = float(oa.get("offered_rps") or 0)
    no_ = float(na.get("offered_rps") or 0)
    osr = float(oa.get("shed_rate") or 0)
    nsr = float(na.get("shed_rate") or 0)
    if oo > 0 and no_ > 0:
        load_delta = _pct(oo, no_)
        if abs(load_delta) < headline_pct:
            rel = _pct(osr, nsr) if osr > 0 else float("inf")
            if nsr > osr + FLEET_SHED_ABS and rel >= phase_pct:
                regressions.append(
                    f"shed_rate {osr:.4f} -> {nsr:.4f} at ~flat offered "
                    f"load ({oo:.4g} -> {no_:.4g} req/s) — the service "
                    "got less able to absorb the same demand")
            elif osr > nsr + FLEET_SHED_ABS:
                improvements.append(
                    f"shed_rate {osr:.4f} -> {nsr:.4f} at ~flat offered "
                    f"load ({oo:.4g} -> {no_:.4g} req/s)")
        else:
            warnings.append(
                f"offered load moved {oo:.4g} -> {no_:.4g} req/s "
                f"({load_delta:+.1f}%) — shed rates ({osr:.4f} vs "
                f"{nsr:.4f}) are not comparable across different demand")
    oar, nar = oa.get("accepted_rps"), na.get("accepted_rps")
    if oar and nar:
        d = _pct(float(oar), float(nar))
        if d <= -headline_pct:
            regressions.append(
                f"accepted throughput {float(oar):.4g} -> "
                f"{float(nar):.4g} req/s ({d:.1f}%, threshold "
                f"-{headline_pct:.0f}%)")
        elif d >= headline_pct:
            improvements.append(
                f"accepted throughput {float(oar):.4g} -> "
                f"{float(nar):.4g} req/s ({d:+.1f}%)")

    os_, ns = old.get("shape") or {}, new.get("shape") or {}
    if os_ and ns and os_ != ns:
        warnings.append(
            f"overload shapes differ (old: {os_}, new: {ns}) — "
            "comparison may not be apples-to-apples")
    return {"headline": headline, "regressions": regressions,
            "warnings": warnings, "improvements": improvements}


def diff_train_fleet(old: dict, new: dict,
                     headline_pct: float = HEADLINE_PCT,
                     phase_pct: float = PHASE_PCT) -> dict:
    """Train-fleet recovery comparison.  The headline is
    mean-time-to-recover, gated at ``phase_pct`` (recovery spans a
    jittered backoff plus process relaunch, so it is noisier than a
    steady-state measurement) and only when BOTH runs actually
    recovered from something — a chaos run against an uninterrupted
    baseline has no MTTR to diff, only its correctness gates.  Those
    gates are never perf tradeoffs: ANY failed iteration means the run
    ended short of its training target (the gang lost work a rollback
    was supposed to save); an exhausted restart budget means the gang
    crash-looped to death; lost iterations growing past the phase
    threshold at the same barrier cadence means rollbacks landed
    further from the failure than they used to."""
    regressions, warnings, improvements = [], [], []
    oa, na = old.get("aux") or {}, new.get("aux") or {}
    osh, nsh = old.get("shape") or {}, new.get("shape") or {}
    if osh and nsh and (osh.get("ranks"), osh.get("barrier_every")) != \
            (nsh.get("ranks"), nsh.get("barrier_every")):
        raise ValueError(
            f"train-fleet shapes differ (old: {osh}, new: {nsh}) — "
            "recovery across different rank counts / barrier cadences "
            "is not comparable")
    ov, nv = float(old.get("value") or 0), float(new.get("value") or 0)
    headline = {"old": ov, "new": nv, "unit": new.get("unit", "s mttr"),
                "delta_pct": None}
    if ov > 0 and nv > 0:
        head = _pct(ov, nv)
        headline["delta_pct"] = round(head, 1)
        if head >= phase_pct:
            regressions.append(
                f"mean time to recover {ov:.4g} -> {nv:.4g} s "
                f"(+{head:.1f}%, threshold +{phase_pct:.0f}%)")
        elif head <= -phase_pct:
            improvements.append(
                f"mean time to recover {ov:.4g} -> {nv:.4g} s "
                f"({head:.1f}%)")
    elif (ov > 0) != (nv > 0):
        side = "old" if ov > 0 else "new"
        warnings.append(
            f"only the {side} run recovered from anything "
            f"({oa.get('recoveries', 0)} vs {na.get('recoveries', 0)} "
            "recoveries) — no MTTR to diff, correctness gates only")

    # correctness gates: these are never perf tradeoffs
    if int(na.get("failed_iterations") or 0) > 0:
        regressions.append(
            f"NEW run FAILED {na['failed_iterations']} iteration(s) "
            f"(reached barrier {na.get('final_barrier')} of "
            f"{na.get('target_iterations')}) — the gang lost training "
            "work a rollback was supposed to save")
    if na.get("budget_exhausted"):
        regressions.append(
            "NEW run exhausted its restart budget "
            f"(spent {na.get('budget_spent')}) — the gang crash-looped "
            "to death instead of finishing")
    ol = int(oa.get("lost_iterations") or 0)
    nl = int(na.get("lost_iterations") or 0)
    if nl > ol and (ol == 0 or _pct(ol, nl) >= phase_pct):
        regressions.append(
            f"lost_iterations {ol} -> {nl} at the same barrier cadence "
            "— rollbacks land further from the failure than they "
            "used to")
    elif ol > nl:
        improvements.append(f"lost_iterations {ol} -> {nl}")
    if int(na.get("world_size_end") or 0) < \
            int(na.get("world_size_start") or 0):
        warnings.append(
            f"NEW run shrank its gang "
            f"({na.get('world_size_start')} -> "
            f"{na.get('world_size_end')} ranks, "
            f"{na.get('shrinks')} shrink(s)) — it finished, but on "
            "fewer hosts than it was given")
    return {"headline": headline, "regressions": regressions,
            "warnings": warnings, "improvements": improvements}


def diff_forest(old: dict, new: dict,
                headline_pct: float = HEADLINE_PCT,
                phase_pct: float = PHASE_PCT) -> dict:
    """Forest-bench comparison: the batched wall under the usual
    headline threshold, PLUS the gates that keep the batching honest —
    the batched-vs-sequential speedup must not shrink past the headline
    threshold (a flat batched wall over a faster sequential engine
    means the fused dispatch stopped paying), ``parity_ok`` false is a
    correctness regression outright (the batched trees diverged from
    their own sequential replay), and a ``grow_traces`` count that grew
    means the one-trace-for-all-models contract broke."""
    regressions, warnings, improvements = [], [], []
    if old.get("num_models") != new.get("num_models"):
        raise ValueError(
            f"forest model counts differ (old: {old.get('num_models')}, "
            f"new: {new.get('num_models')}) — batched walls across "
            "different sweep widths are not comparable")
    unit = new.get("unit", "s")
    ov, nv = float(old["value"]), float(new["value"])
    head = _pct(ov, nv)
    headline = {"old": ov, "new": nv, "unit": unit,
                "delta_pct": round(head, 1),
                "num_models": new.get("num_models")}
    if head >= headline_pct:
        regressions.append(
            f"headline {unit} {ov:.4g} -> {nv:.4g} (+{head:.1f}%, "
            f"threshold +{headline_pct:.0f}%)")
    elif head <= -headline_pct:
        improvements.append(
            f"headline {unit} {ov:.4g} -> {nv:.4g} ({head:.1f}%)")

    oa, na = old.get("aux") or {}, new.get("aux") or {}
    osp, nsp = oa.get("speedup"), na.get("speedup")
    if osp and nsp:
        d = _pct(float(osp), float(nsp))
        if d <= -headline_pct:
            regressions.append(
                f"batched-vs-sequential speedup {osp:.2f}x -> {nsp:.2f}x "
                f"({d:.1f}%, threshold -{headline_pct:.0f}%) — the fused "
                "dispatch pays less than it used to")
        elif d >= headline_pct:
            improvements.append(
                f"batched-vs-sequential speedup {osp:.2f}x -> {nsp:.2f}x "
                f"({d:+.1f}%)")
    if nsp is not None and float(nsp) < 1.0:
        regressions.append(
            f"NEW speedup {float(nsp):.2f}x < 1 — the batched program is "
            "slower than the sequential loop it replaces")

    # correctness gates: these are never perf tradeoffs
    if new.get("parity_ok") is False:
        regressions.append(
            "NEW run's per-model parity hashes do not match the "
            "sequential replay (parity_ok false) — the batched grower "
            "diverged from the tree-by-tree path")
    ot = oa.get("grow_traces")
    nt = na.get("grow_traces")
    if nt is not None and ot is not None and int(nt) > int(ot):
        regressions.append(
            f"grow_traces {ot} -> {nt} — the batched sweep retraces; "
            "one-program-for-the-forest no longer holds")
    op_, np_ = old.get("parity") or {}, new.get("parity") or {}
    if op_ and np_ and sorted(op_) == sorted(np_) and op_ != np_:
        changed = sorted(k for k in op_ if op_[k] != np_.get(k))
        warnings.append(
            "per-model parity hashes changed vs the OLD artifact "
            f"({len(changed)}/{len(op_)} models: "
            + ", ".join(changed[:4])
            + (" ..." if len(changed) > 4 else "")
            + ") — the trained trees themselves moved, expected only "
            "after an intentional numerics change")

    os_, ns = old.get("shape") or {}, new.get("shape") or {}
    if os_ and ns and os_ != ns:
        warnings.append(
            f"sweep shapes differ (old: {os_}, new: {ns}) — comparison "
            "may not be apples-to-apples")
    return {"headline": headline, "regressions": regressions,
            "warnings": warnings, "improvements": improvements}


def diff_multichip(old: dict, new: dict,
                   headline_pct: float = HEADLINE_PCT,
                   phase_pct: float = PHASE_PCT) -> dict:
    """Multichip comparison: headline under the usual threshold, plus
    the skew-regression gate — a cross-rank skew (max−min seconds of a
    span/collective series) growing past ``phase_pct`` above the
    absolute floor is a regression even when the headline stays flat
    (one straggling rank hides inside an aggregate mean)."""
    regressions, warnings, improvements = [], [], []
    if old.get("world") != new.get("world"):
        raise ValueError(
            f"multichip world sizes differ (old: {old.get('world')}, "
            f"new: {new.get('world')}) — skew across different worlds "
            "is not comparable")
    unit = new.get("unit", "s")
    ov, nv = float(old["value"]), float(new["value"])
    head = _pct(ov, nv)
    headline = {"old": ov, "new": nv, "unit": unit,
                "delta_pct": round(head, 1), "world": new.get("world")}
    if head >= headline_pct:
        regressions.append(
            f"headline {unit} {ov:.4g} -> {nv:.4g} (+{head:.1f}%, "
            f"threshold +{headline_pct:.0f}%)")
    elif head <= -headline_pct:
        improvements.append(
            f"headline {unit} {ov:.4g} -> {nv:.4g} ({head:.1f}%)")

    osk, nsk = old.get("skew") or {}, new.get("skew") or {}
    for name in sorted(set(osk) ^ set(nsk)):
        side = "old" if name in osk else "new"
        warnings.append(
            f"skew series '{name}' present only in the {side} artifact "
            "— instrumentation coverage changed between the two runs")
    for name in sorted(set(osk) & set(nsk)):
        o = float((osk[name] or {}).get("max_minus_min_s") or 0.0)
        n = float((nsk[name] or {}).get("max_minus_min_s") or 0.0)
        if n <= SKEW_ABS_FLOOR_S and o <= SKEW_ABS_FLOOR_S:
            continue  # both inside scheduling noise
        if o <= 0:
            # a skew APPEARING from a clean baseline is the worst
            # straggler regression, not a footnote — a 0s -> 5s skew
            # must never pass a gate a 0.03s -> 0.04s one fails
            regressions.append(
                f"cross-rank skew '{name}' appeared: 0 -> {n:.4f}s "
                f"max-min (implicated rank "
                f"{(nsk[name] or {}).get('max_rank')})")
            continue
        d = _pct(o, n)
        who = (nsk[name] or {}).get("min_rank") \
            if name.endswith(".wait_s") else (nsk[name] or {}).get("max_rank")
        if d >= phase_pct and n > SKEW_ABS_FLOOR_S:
            regressions.append(
                f"cross-rank skew '{name}' {o:.4f}s -> {n:.4f}s max-min "
                f"(+{d:.1f}%, threshold +{phase_pct:.0f}%; implicated "
                f"rank {who})")
        elif d <= -phase_pct and o > SKEW_ABS_FLOOR_S:
            improvements.append(
                f"cross-rank skew '{name}' {o:.4f}s -> {n:.4f}s "
                f"({d:.1f}%)")

    _diff_hbm(old, new, regressions, warnings, improvements,
              headline_pct)
    # per-rank memory skew: a rank whose peak diverges from its peers
    # is the data-balance analog of a time straggler
    orh = old.get("rank_hbm_peak_bytes") or {}
    nrh = new.get("rank_hbm_peak_bytes") or {}
    if len(nrh) >= 2:
        mx, mn = max(nrh.values()), min(nrh.values())
        if mn > 0 and _pct(mn, mx) >= phase_pct:
            omx, omn = (max(orh.values()), min(orh.values())) \
                if len(orh) >= 2 else (0, 0)
            was_skewed = omn > 0 and _pct(omn, omx) >= phase_pct
            who = max(nrh, key=lambda r: nrh[r])
            msg = (f"per-rank hbm_peak_bytes skew: min {mn}, max {mx} "
                   f"(+{_pct(mn, mx):.1f}%; heaviest rank {who})")
            if was_skewed:
                warnings.append(msg + " — already skewed in baseline")
            else:
                regressions.append("memory skew appeared: " + msg)

    oc = old.get("collective_census") or {}
    nc = new.get("collective_census") or {}
    if oc and nc and oc != nc:
        changed = sorted(k for k in set(oc) | set(nc)
                         if oc.get(k) != nc.get(k))
        warnings.append(
            "collective census changed (the per-op contract moved): "
            + ", ".join(f"{k} {oc.get(k, 0)} -> {nc.get(k, 0)}"
                        for k in changed[:6])
            + (" ..." if len(changed) > 6 else ""))
    for s in new.get("stragglers") or []:
        warnings.append(
            f"NEW run names a straggler: rank {s.get('straggler_rank')} "
            f"at {s.get('site')} (wait skew {s.get('wait_skew_s')}s)")
    return {"headline": headline, "regressions": regressions,
            "warnings": warnings, "improvements": improvements}


def diff(old: dict, new: dict, headline_pct: float = HEADLINE_PCT,
         phase_pct: float = PHASE_PCT) -> dict:
    """Compare two normalized records; returns
    ``{regressions: [...], warnings: [...], improvements: [...],
    headline: {...}}``."""
    if "train_fleet" in (old.get("kind"), new.get("kind")):
        if old.get("kind") != new.get("kind"):
            raise ValueError(
                f"{old['label']} is a {old.get('kind')} artifact, "
                f"{new['label']} is a {new.get('kind')} artifact — "
                "train-fleet recovery metrics and other results are "
                "not comparable (an MTTR has no meaning against a "
                "latency or s/tree headline)")
        return diff_train_fleet(old, new, headline_pct, phase_pct)
    if "fleet" in (old.get("kind"), new.get("kind")):
        if old.get("kind") != new.get("kind"):
            raise ValueError(
                f"{old['label']} is a {old.get('kind')} artifact, "
                f"{new['label']} is a {new.get('kind')} artifact — "
                "fleet-overload and other results are not comparable "
                "(an overload shed-rate has no meaning against a "
                "steady-state serving bench)")
        return diff_fleet(old, new, headline_pct, phase_pct)
    if "forest" in (old.get("kind"), new.get("kind")):
        if old.get("kind") != new.get("kind"):
            raise ValueError(
                f"{old['label']} is a {old.get('kind')} artifact, "
                f"{new['label']} is a {new.get('kind')} artifact — "
                "forest-bench and other results are not comparable")
        return diff_forest(old, new, headline_pct, phase_pct)
    if "multichip" in (old.get("kind"), new.get("kind")):
        if old.get("kind") != new.get("kind"):
            raise ValueError(
                f"{old['label']} is a {old.get('kind')} artifact, "
                f"{new['label']} is a {new.get('kind')} artifact — "
                "multichip and other results are not comparable")
        return diff_multichip(old, new, headline_pct, phase_pct)
    if "serving" in (old.get("kind"), new.get("kind")):
        if old.get("kind") != new.get("kind"):
            raise ValueError(
                f"{old['label']} is a {old.get('kind')} artifact, "
                f"{new['label']} is a {new.get('kind')} artifact — "
                "serving and training results are not comparable")
        return diff_serving(old, new, headline_pct, phase_pct)
    regressions, warnings, improvements = [], [], []

    if old.get("metric") and new.get("metric") \
            and old["metric"] != new["metric"]:
        warnings.append(
            f"metric mismatch: {old['metric']} vs {new['metric']} — "
            "comparison may not be apples-to-apples")

    # an errored/empty NEW run is the worst regression of all, not a
    # -100% improvement (bench.py's crash path emits value 0.0 + error)
    if new.get("error"):
        regressions.append(f"NEW run errored: {new['error']}")
    if old.get("error"):
        warnings.append(f"OLD run errored: {old['error']} — baseline "
                        "side is not a real measurement")
    ov, nv = float(old.get("value") or 0), float(new.get("value") or 0)
    headline = {"old_s_per_tree": ov, "new_s_per_tree": nv,
                "delta_pct": None}
    if nv <= 0 and not new.get("error"):
        regressions.append("NEW run has no headline value")
    if ov > 0 and nv > 0:
        head = _pct(ov, nv)
        headline["delta_pct"] = round(head, 1)
        if head >= headline_pct:
            regressions.append(
                f"headline s/tree {ov:.4f} -> {nv:.4f} "
                f"(+{head:.1f}%, threshold +{headline_pct:.0f}%)")
        elif head <= -headline_pct:
            improvements.append(
                f"headline s/tree {ov:.4f} -> {nv:.4f} ({head:.1f}%)")

    ovb, nvb = old.get("vs_baseline"), new.get("vs_baseline")
    if ovb and nvb:
        headline["vs_baseline"] = {"old": ovb, "new": nvb}
        if float(nvb) < 0.85 * float(ovb):
            regressions.append(
                f"vs_baseline {ovb} -> {nvb} "
                f"({_pct(float(ovb), float(nvb)):.1f}%)")

    # per-phase regressions: only comparable when both runs attributed
    # phases (a missing breakdown is reported, never silently skipped)
    op, np_ = old.get("phases") or {}, new.get("phases") or {}
    shared = sorted(set(op) & set(np_) - {"unattributed"})
    if op or np_:
        if not shared:
            warnings.append("phase breakdowns not comparable "
                            f"(old: {sorted(op)}, new: {sorted(np_)})")
        # a phase present on only ONE side is itself a signal (lost
        # scope attribution, or work that moved to/from unattributed)
        # — never drop it silently
        for ph in sorted(set(op) ^ set(np_)):
            side = "old" if ph in op else "new"
            val = op.get(ph, np_.get(ph, 0.0))
            warnings.append(
                f"phase '{ph}' ({val:.3f}s) present only in the {side} "
                "run — attribution changed between the two runs")
        for ph in shared:
            o, n = float(op[ph]), float(np_[ph])
            if o <= 0 or n <= 0:
                # a 0.0 side has no meaningful percent (bucket_events
                # keeps 0.0-second entries); only a real appearance is
                # worth a word
                if max(o, n) > 0.05:
                    warnings.append(
                        f"phase '{ph}' {o:.3f}s -> {n:.3f}s (no "
                        "baseline to diff against)")
                continue
            d = _pct(o, n)
            if d >= phase_pct:
                regressions.append(
                    f"phase '{ph}' {o:.3f}s -> {n:.3f}s "
                    f"(+{d:.1f}%, threshold +{phase_pct:.0f}%)")
            elif d <= -phase_pct:
                improvements.append(
                    f"phase '{ph}' {o:.3f}s -> {n:.3f}s ({d:.1f}%)")
    else:
        warnings.append("no phase breakdown on either side (capture one "
                        "with LGBM_TPU_TRACE=<dir> bench.py)")

    # compile hygiene of the NEW run (the round-5 mechanism: lazy
    # compiles inside the timed loop)
    if new.get("compiles_timed"):
        regressions.append(
            f"{new['compiles_timed']} backend compile(s) inside the NEW "
            "run's timed loop — the measurement itself is dirty")
    if new.get("compile_stable") is False:
        warnings.append("NEW run's warm-up never went compile-stable "
                        "(BENCH_MAX_WARM exhausted)")

    for k in ("train_auc", "valid_auc"):
        if old.get(k) is not None and new.get(k) is not None:
            d = float(new[k]) - float(old[k])
            if d < -AUC_ABS:
                regressions.append(f"{k} {old[k]} -> {new[k]} ({d:+.4f})")

    _diff_hbm(old, new, regressions, warnings, improvements,
              headline_pct)

    return {"headline": headline, "regressions": regressions,
            "warnings": warnings, "improvements": improvements}


def driver_row(rec: dict) -> str:
    """The bench row ROADMAP item 1 requires in perf-motivated
    serial.py/record.py commits — ready to paste."""
    sha = (rec.get("sha") or "unknown")[:9]
    knobs = ",".join(f"{k.split('LGBM_TPU_')[-1]}={v}"
                     for k, v in (rec.get("knobs") or {}).items()) or "-"
    return ("| {metric} | {value} s/tree | vs_baseline {vsb} | "
            "{platform} | warm {w}/{d} compiles {cw}+{ct} | {knobs} | "
            "{sha} |").format(
        metric=rec.get("metric", "?"), value=rec.get("value", "?"),
        vsb=rec.get("vs_baseline", "?"),
        platform=rec.get("platform", "?"),
        w=rec.get("warmup_iters", "?"),
        d=rec.get("warm_trees_discarded", "?"),
        cw=rec.get("compiles_warmup", "?"),
        ct=rec.get("compiles_timed", "?"),
        knobs=knobs, sha=sha)


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--threshold", type=float, default=HEADLINE_PCT,
                    help="headline regression threshold in percent "
                         f"(default {HEADLINE_PCT:.0f})")
    ap.add_argument("--phase-threshold", type=float, default=PHASE_PCT,
                    help="per-phase regression threshold in percent "
                         f"(default {PHASE_PCT:.0f})")
    ap.add_argument("--json", help="also write the full report here")
    args = ap.parse_args(argv)

    try:
        old, new = normalize(args.old), normalize(args.new)
        report = diff(old, new, args.threshold, args.phase_threshold)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"benchdiff: {e}", file=sys.stderr)
        return 2

    h = report["headline"]
    print(f"benchdiff: {old['label']} -> {new['label']}")
    delta = ("n/a" if h["delta_pct"] is None
             else f"{h['delta_pct']:+.1f}%")
    if new.get("kind") == "multichip":
        print(f"  headline: {h['old']:.4g} -> {h['new']:.4g} "
              f"{h['unit']} ({delta}) at world={h.get('world')}")
    elif new.get("kind") == "forest":
        print(f"  headline: {h['old']:.4g} -> {h['new']:.4g} "
              f"{h['unit']} ({delta}) at num_models="
              f"{h.get('num_models')}")
    elif new.get("kind") == "train_fleet":
        aux = new.get("aux") or {}
        print(f"  headline: {h['old']:.4g} -> {h['new']:.4g} "
              f"{h['unit']} ({delta}) over "
              f"{aux.get('recoveries', 0)} recovery(ies), "
              f"{aux.get('lost_iterations', 0)} lost iteration(s)")
    elif new.get("kind") in ("serving", "fleet"):
        print(f"  headline: {h['old']:.4g} -> {h['new']:.4g} "
              f"{h['unit']} ({delta})")
    else:
        print(f"  headline: {h['old_s_per_tree']:.4f} -> "
              f"{h['new_s_per_tree']:.4f} s/tree ({delta})")
    for r in report["regressions"]:
        print(f"  REGRESSION: {r}")
    for w in report["warnings"]:
        print(f"  warning: {w}")
    for i in report["improvements"]:
        print(f"  improvement: {i}")
    if new.get("kind") not in ("serving", "multichip", "forest",
                               "fleet", "train_fleet"):
        print("  driver-config row (paste into the commit message):")
        print("  " + driver_row(new))

    if args.json:
        # atomic (tmp + rename, the resilience.atomic protocol inlined —
        # this tool stays dependency-free): a preempted benchdiff must
        # never leave half a JSON under the artifact name
        tmp = f"{args.json}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump({"old": old, "new": new, "report": report}, fh,
                      indent=1)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, args.json)
    # diff semantics: 1 means "differences (regressions) found"
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
