#!/bin/bash
# Round-5 TPU-window watcher: probe the axon tunnel and run the pending
# round-5 measurement stages in priority order whenever it is alive
# (same marker-file design as tools/tpu_watch.sh; windows are short and
# unpredictable, so progress must accumulate per stage).
#
#   bash tools/tpu_watch5.sh [outdir]

set -u
OUT=${1:-/tmp/tpu_watch5}
POLL_S=${POLL_S:-120}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

probe() {
  timeout 75 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((8, 8)); (x @ x).block_until_ready()
assert jax.devices()[0].platform == "tpu", jax.devices()
EOF
}

run() {  # <name> <timeout_s> <max_attempts> <cmd...>
  local name=$1 tmo=$2 maxtry=$3; shift 3
  [ -e "$OUT/$name.ok" ] && return 0
  [ -e "$OUT/$name.giveup" ] && return 0
  local tries=0
  [ -e "$OUT/$name.tries" ] && tries=$(cat "$OUT/$name.tries")
  if [ "$tries" -ge "$maxtry" ]; then touch "$OUT/$name.giveup"; return 0; fi
  echo "[$(date -u +%H:%M:%S)] [$name] attempt $((tries+1))/$maxtry"
  if timeout "$tmo" "$@" >"$OUT/$name.out" 2>&1; then
    # evidence the run reached the chip (json rows carry platform)
    if grep -q '"platform": *"tpu"' "$OUT/$name.out" \
       || grep -q 'platform.*tpu' "$OUT/$name.out"; then
      touch "$OUT/$name.ok"
      echo "[$(date -u +%H:%M:%S)] [$name] OK"
      return 1
    fi
    echo "[$(date -u +%H:%M:%S)] [$name] rc=0 but no TPU evidence"
    return 1
  fi
  echo $((tries+1)) > "$OUT/$name.tries"
  echo "[$(date -u +%H:%M:%S)] [$name] failed (rc=$?)"
  return 1
}

all_done() {
  for s in northstar predictbench bench10m; do
    [ -e "$OUT/$s.ok" ] || [ -e "$OUT/$s.giveup" ] || return 1
  done
  return 0
}

while ! all_done; do
  if probe; then
    run northstar 4500 3 env NS_REF=0 BENCH_REQUIRE_TPU=1 \
        python tools/northstar_run.py && \
    run predictbench 3000 3 env BENCH_REQUIRE_TPU=1 \
        python tools/bench_predict.py && \
    run bench10m 3000 3 env BENCH_REQUIRE_TPU=1 BENCH_ROWS=10000000 \
        BENCH_TREES=20 BENCH_BUDGET_S=1800 python bench.py
  else
    echo "[$(date -u +%H:%M:%S)] tunnel dead"
  fi
  all_done && break
  sleep "$POLL_S"
done
echo "[$(date -u +%H:%M:%S)] round-5 stages done"
